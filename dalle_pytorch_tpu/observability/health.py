"""In-graph training-health diagnostics.

PR 1's telemetry answers "where does the time go"; this module answers
"where does the training go wrong": per-layer gradient/parameter/update
norms, NaN/Inf localization, activation statistics captured from inside the
model (attention logits, dVAE codebook usage), and host-side divergence
alarms with state that survives checkpoint restarts.

Design split — two strictly separated halves:

* **In-graph half** (`tree_health`, `per_leaf_norms`, `nonfinite_counts`,
  the tap machinery): pure jax functions traced INSIDE the jitted train
  step.  They never synchronize with the host — no `.item()`, `float()`,
  `np.asarray`, or `jax.device_get` (enforced by `tools/lint_host_sync.py`).
  The train step exposes them behind a static `with_health` argument, so the
  health-off executable's HLO is byte-identical to a build without any of
  this code: diagnostics are a SECOND compiled executable the training loop
  dispatches every `--health_every` steps, not a tax on every step.

* **Host half** (`leaf_paths`, `first_nonfinite`, `publish`,
  `DivergenceMonitor`): consumes the health pytree after the training loop
  fetched it (the one deliberate device→host sync, paid only on health
  steps), converts per-leaf vectors back into path-named records, feeds the
  metrics registry, and raises threshold alarms through the telemetry event
  stream (`kind: "alarm"` — same path recompile/FLOPs alarms use).

The per-leaf vectors are ordered by `jax.tree_util.tree_flatten_with_path`
over the parameter pytree; `leaf_paths(params)` gives the matching names.
For `--scan_layers` configs a stacked leaf carries all depth layers in one
array, so "per layer" degrades to "per stacked parameter" there (localizing
inside a scanned stack would need a per-slice reduction; not done yet).

Activation taps
---------------

Model code exports intermediate statistics through a trace-time capture
context:

    with health.capture_taps() as taps:
        loss = loss_fn(params, batch, key)   # attend()/flash/etc call tap()
    # taps: {name: {stat: traced f32 scalar}} — merge into the step outputs

`tap()` is a no-op (zero added HLO) unless a capture context is active on
the current thread.  Taps must only fire in a plain forward — recording
tracers from inside `jax.grad`'s trace would leak them — so the diagnostic
step runs one extra probe forward (first microbatch) under the capture
context rather than tapping the differentiated forward.
"""
from __future__ import annotations

import contextlib
import threading
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp

_EPS = 1e-12


# ---------------------------------------------------------------------------
# activation taps (trace-time capture of model intermediates)
# ---------------------------------------------------------------------------

class _TapState(threading.local):
    def __init__(self):
        self.sink: Optional[Dict[str, Dict[str, jnp.ndarray]]] = None
        self.trace = None  # the jax trace active when capture started
        self.skipped = 0


_TAP = _TapState()


def _cur_trace():
    """The currently-active jax trace object (identity is the token: scan /
    checkpoint / inner-jit bodies trace under a DIFFERENT object than the
    enclosing trace).  None when this jax version has no trace_ctx — the
    guard then degrades to 'record everything' (pre-stackless-tracing jax
    raised on the leak anyway, so nothing is lost)."""
    try:
        return jax.core.trace_ctx.trace
    except AttributeError:  # pragma: no cover - jax < 0.4.34
        return None


def taps_active() -> bool:
    """True iff a `capture_taps()` context is active on this thread.
    Instrumented code guards stat computation on this, so the health-off
    trace contains zero extra ops."""
    return _TAP.sink is not None


@contextlib.contextmanager
def capture_taps():
    """Collect `tap()` records emitted while tracing the enclosed block.
    Yields the sink dict: {name: {stat_name: scalar}}.  Values are traced
    arrays belonging to the enclosing trace — consume them there (e.g. merge
    into the step's output pytree); do not stash them past the trace.

    Taps fired from INSIDE a nested trace — a `lax.scan` body
    (`--scan_layers`), a `jax.checkpoint` region (`--execution remat`), a
    nested jit — are DROPPED, not recorded: their tracers cannot legally
    escape into this context's trace, and recording them would crash the
    diagnostic step with UnexpectedTracerError at its first use on exactly
    the remat/scan flagship configs.  `taps_skipped()` reports how many were
    dropped; top-level taps (output logits, dVAE codebook) always survive."""
    prev, prev_trace, prev_skipped = _TAP.sink, _TAP.trace, _TAP.skipped
    _TAP.sink = sink = {}
    _TAP.trace = _cur_trace()
    _TAP.skipped = 0
    try:
        yield sink
    finally:
        _TAP.sink = prev
        _TAP.trace = prev_trace
        # keep the skip count readable after exit (reset on next capture)
        if prev is not None:
            _TAP.skipped = prev_skipped


def taps_skipped() -> int:
    """Taps dropped by the most recent capture because they fired inside a
    nested trace (scan/remat/inner-jit bodies)."""
    return _TAP.skipped


def tap(name: str, **stats) -> None:
    """Record named scalar statistics into the active capture (no-op when
    none).  Repeated names get a numeric suffix (layer 2's attention tap
    lands beside layer 1's, not on top of it).  Calls from inside a nested
    trace are dropped — see capture_taps()."""
    sink = _TAP.sink
    if sink is None:
        return
    if _TAP.trace is not None and _cur_trace() is not _TAP.trace:
        _TAP.skipped += 1
        return
    base, i = name, 1
    while name in sink:
        i += 1
        name = f"{base}_{i}"
    sink[name] = {k: jnp.asarray(v, jnp.float32) for k, v in stats.items()}


def tap_attention(name: str, scores: Optional[jnp.ndarray] = None,
                  probs: Optional[jnp.ndarray] = None,
                  lse: Optional[jnp.ndarray] = None) -> None:
    """Attention-numerics tap from whatever intermediate the implementation
    has on hand.  Dense attention passes `scores` (pre-softmax logits, f32)
    and `probs` (exact max-logit + row-entropy); the flash kernel only
    exports its logsumexp rows, so the fused path passes `lse` — lse bounds
    the row max (max ≤ lse ≤ max + log n) and is the saturation signal the
    bf16 overflow hunt needs."""
    if not taps_active():
        return
    stats: Dict[str, jnp.ndarray] = {}
    if scores is not None:
        s32 = scores.astype(jnp.float32)
        stats["logit_max"] = jnp.max(s32)
        # mean of per-row maxes, not the raw mean — masked positions carry
        # finfo.min fills that would swamp a plain mean (every causal row
        # has at least its diagonal live)
        stats["logit_rowmax_mean"] = jnp.mean(jnp.max(s32, axis=-1))
    if probs is not None:
        p32 = probs.astype(jnp.float32)
        ent = -jnp.sum(p32 * jnp.log(p32 + 1e-20), axis=-1)
        stats["entropy_mean"] = jnp.mean(ent)
        stats["entropy_min"] = jnp.min(ent)
    if lse is not None:
        l32 = lse.astype(jnp.float32)
        stats["lse_max"] = jnp.max(l32)
        stats["lse_mean"] = jnp.mean(l32)
    if stats:
        tap(name, **stats)


# ---------------------------------------------------------------------------
# in-graph numerics (pure; called inside the jitted step)
# ---------------------------------------------------------------------------

def per_leaf_norms(tree: Any) -> jnp.ndarray:
    """(n_leaves,) f32 L2 norm of every leaf, flatten order.  Per-leaf fused
    reductions — no f32 copy of the tree is materialized."""
    leaves = jax.tree_util.tree_leaves(tree)
    return jnp.stack([
        jnp.sqrt(jnp.sum(jnp.square(l.astype(jnp.float32)))) for l in leaves
    ])


def nonfinite_counts(tree: Any) -> jnp.ndarray:
    """(n_leaves,) int32 count of non-finite elements per leaf."""
    leaves = jax.tree_util.tree_leaves(tree)
    return jnp.stack([
        jnp.sum(~jnp.isfinite(l.astype(jnp.float32))).astype(jnp.int32)
        for l in leaves
    ])


def tree_health(params: Any, grads: Any, new_params: Any) -> Dict[str, jnp.ndarray]:
    """The core per-layer numerics pytree, computed in-graph.

    grads are whatever the optimizer is about to consume (post-unscale,
    post-clip when clipping is on — the APPLIED gradients).  The update is
    measured as `new_params - params` in f32, which captures the REALIZED
    update — including stochastic-rounding loss under bf16 param storage and
    the all-zero update of a loss-scale skip step.

    `param_nonfinite` is computed on the INPUT params, not the updated ones:
    once a single poisoned weight has driven the loss NaN, the post-update
    params are non-finite EVERYWHERE (NaN grads reach every leaf through the
    optimizer) — the pre-step params are the tree that still localizes the
    original offender."""
    grad_norm = per_leaf_norms(grads)
    param_norm = per_leaf_norms(params)
    upd = jax.tree_util.tree_map(
        lambda new, old: new.astype(jnp.float32) - old.astype(jnp.float32),
        new_params, params,
    )
    update_norm = per_leaf_norms(upd)
    return {
        "grad_norm": grad_norm,
        "param_norm": param_norm,
        "update_norm": update_norm,
        "update_ratio": update_norm / (param_norm + _EPS),
        "grad_nonfinite": nonfinite_counts(grads),
        "param_nonfinite": nonfinite_counts(params),
        "grad_norm_global": jnp.sqrt(jnp.sum(jnp.square(grad_norm))),
    }


# ---------------------------------------------------------------------------
# leaf naming (trace-time/static — no device sync)
# ---------------------------------------------------------------------------

def _path_str(path) -> str:
    ju = jax.tree_util
    segs = []
    for p in path:
        if isinstance(p, ju.DictKey):
            segs.append(str(p.key))
        elif isinstance(p, ju.SequenceKey):
            segs.append(str(p.idx))
        elif isinstance(p, ju.GetAttrKey):
            segs.append(p.name)
        else:
            segs.append(str(p))
    return "/".join(segs)


def leaf_paths(tree: Any) -> List[str]:
    """Path name per leaf, in the flatten order the per-leaf vectors use."""
    with_path, _ = jax.tree_util.tree_flatten_with_path(tree)
    return [_path_str(p) for p, _ in with_path]
