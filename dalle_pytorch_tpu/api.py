"""Object-style API facade.

The functional core (configs + init/apply functions) is the real interface,
but users coming from the reference expect `DiscreteVAE(...)`, `DALLE(dim=...,
vae=vae, ...)`, `CLIP(...)` objects with methods (README usage,
/root/reference/README.md:77-304).  These thin wrappers bundle (config,
params, PRNG key) and delegate to the functional modules — no hidden state
beyond the parameter pytree they carry.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from dalle_pytorch_tpu.models import clip as _clip
from dalle_pytorch_tpu.models import dalle as _dalle
from dalle_pytorch_tpu.models import sampling as _sampling
from dalle_pytorch_tpu.models import vae as _vae


def _as_key(key_or_seed):
    if isinstance(key_or_seed, int):
        return jax.random.PRNGKey(key_or_seed)
    return key_or_seed


class DiscreteVAE:
    def __init__(self, key=0, params: Optional[dict] = None, **cfg_kwargs):
        self.cfg = _vae.DiscreteVAEConfig(**cfg_kwargs)
        self.params = params if params is not None else _vae.init_discrete_vae(_as_key(key), self.cfg)

    # reference attribute surface
    @property
    def image_size(self):
        return self.cfg.image_size

    @property
    def num_tokens(self):
        return self.cfg.num_tokens

    @property
    def num_layers(self):
        return self.cfg.num_layers

    @property
    def channels(self):
        return self.cfg.channels

    def __call__(self, images, key=None, return_loss=False, return_recons=False, temp=None):
        return _vae.forward(
            self.params, self.cfg, images, key=_as_key(key if key is not None else 0),
            return_loss=return_loss, return_recons=return_recons, temp=temp,
        )

    forward = __call__

    def get_codebook_indices(self, images):
        return _vae.get_codebook_indices(self.params, self.cfg, images)

    def decode(self, img_seq):
        return _vae.decode_indices(self.params, self.cfg, img_seq)


class DALLE:
    def __init__(self, *, vae: DiscreteVAE, key=1, params: Optional[dict] = None, **cfg_kwargs):
        self.vae = vae
        self.cfg = _dalle.DALLEConfig.from_vae(vae.cfg, **cfg_kwargs)
        self.params = params if params is not None else _dalle.init_dalle(_as_key(key), self.cfg)
        # AOT prefill/decode executables keyed by (batch, cond_scale,
        # prime_len, filter_thres): repeated generate_images calls at the
        # same shape never re-trace (hits/misses in the metrics registry)
        self._exec_cache = _sampling.ExecutableCache()

    @property
    def text_seq_len(self):
        return self.cfg.text_seq_len

    @property
    def image_seq_len(self):
        return self.cfg.image_seq_len

    @property
    def total_seq_len(self):
        return self.cfg.total_seq_len

    def __call__(self, text, image=None, return_loss=False, null_cond_prob=0.0, key=None):
        """image: raw pixels (B, H, W, C) or code ids (B, image_seq_len)."""
        codes = image
        if image is not None and image.ndim == 4:
            codes = jax.lax.stop_gradient(self.vae.get_codebook_indices(image))
        return _dalle.forward(
            self.params, self.cfg, text, codes, return_loss=return_loss,
            null_cond_prob=null_cond_prob, key=key,
        )

    forward = __call__

    def generate_images(self, text, key=0, clip=None, filter_thres=0.5, temperature=1.0,
                        img=None, num_init_img_tokens=None, cond_scale=1.0,
                        use_exec_cache=True):
        return _sampling.generate_images(
            self.params, self.cfg, self.vae.params, self.vae.cfg, text, _as_key(key),
            filter_thres=filter_thres, temperature=temperature, img=img,
            num_init_img_tokens=num_init_img_tokens, cond_scale=cond_scale,
            clip_params=clip.params if clip is not None else None,
            clip_cfg=clip.cfg if clip is not None else None,
            exec_cache=self._exec_cache if use_exec_cache else None,
        )

    def generate_texts(self, tokenizer=None, text=None, key=0, filter_thres=0.5, temperature=1.0):
        prompt = None
        if isinstance(text, str):
            assert tokenizer is not None
            ids = tokenizer.encode(text)
            prompt = jnp.asarray([ids], jnp.int32)
        elif text is not None:
            prompt = text
        tokens = _sampling.generate_texts(
            self.params, self.cfg, _as_key(key), text=prompt,
            filter_thres=filter_thres, temperature=temperature,
        )
        texts = None
        if tokenizer is not None:
            pad_tokens = set(
                range(self.cfg.num_text_tokens_padded - self.cfg.text_seq_len,
                      self.cfg.num_text_tokens_padded)
            )
            import numpy as np

            texts = [tokenizer.decode(np.asarray(t), pad_tokens=pad_tokens) for t in tokens]
        return tokens, texts


class CLIP:
    def __init__(self, key=2, params: Optional[dict] = None, **cfg_kwargs):
        self.cfg = _clip.CLIPConfig(**cfg_kwargs)
        self.params = params if params is not None else _clip.init_clip(_as_key(key), self.cfg)

    def __call__(self, text, images, text_mask=None, return_loss=False):
        return _clip.forward(self.params, self.cfg, text, images, text_mask=text_mask,
                             return_loss=return_loss)

    forward = __call__


class OpenAIDiscreteVAE:
    """Pretrained OpenAI dVAE.  With no arguments the published pickles are
    downloaded to the cache and converted once (reference vae.py:104-117);
    explicit encoder/decoder paths skip the download."""

    def __init__(self, encoder_path: Optional[str] = None, decoder_path: Optional[str] = None):
        from dalle_pytorch_tpu.models import openai_vae as _ovae

        if (encoder_path is None) != (decoder_path is None):
            raise ValueError("provide both encoder_path and decoder_path, or neither")
        if encoder_path is None:
            from dalle_pytorch_tpu.models.pretrained import load_openai_vae_pretrained

            self.params, self.cfg = load_openai_vae_pretrained()
        else:
            self.cfg = _ovae.OpenAIVAEConfig()
            self.params = _ovae.load_openai_vae(encoder_path, decoder_path)
        self._mod = _ovae

    image_size = 256
    num_layers = 3
    num_tokens = 8192
    channels = 3

    def get_codebook_indices(self, images):
        return self._mod.get_codebook_indices(self.params, self.cfg, images)

    def decode(self, img_seq):
        return self._mod.decode_indices(self.params, self.cfg, img_seq)


class VQGanVAE:
    """Pretrained taming VQGAN/GumbelVQ (weights converted from a checkpoint
    via models/vqgan.load_vqgan)."""

    def __init__(self, vqgan_model_path: Optional[str] = None, vqgan_config: Optional[dict] = None):
        from dalle_pytorch_tpu.models import vqgan as _vqgan

        if vqgan_model_path is None:
            if vqgan_config is not None:
                raise ValueError("a custom vqgan_config requires its vqgan_model_path")
            from dalle_pytorch_tpu.models.pretrained import load_vqgan_pretrained

            self.params, self.cfg = load_vqgan_pretrained()
        else:
            self.params, self.cfg = _vqgan.load_vqgan(vqgan_model_path, vqgan_config)
        self._mod = _vqgan

    @property
    def image_size(self):
        return self.cfg.image_size

    @property
    def num_layers(self):
        return self.cfg.num_layers

    @property
    def num_tokens(self):
        return self.cfg.num_tokens

    @property
    def channels(self):
        return self.cfg.channels

    @property
    def is_gumbel(self):
        return self.cfg.is_gumbel

    def get_codebook_indices(self, images):
        return self._mod.get_codebook_indices(self.params, self.cfg, images)

    def decode(self, img_seq):
        return self._mod.decode_indices(self.params, self.cfg, img_seq)
