from setuptools import find_packages, setup

exec(open("dalle_pytorch_tpu/version.py").read())

setup(
    name="dalle-pytorch-tpu",
    packages=find_packages(exclude=["tests"]),
    include_package_data=True,
    package_data={"dalle_pytorch_tpu": ["data/vocab/*.txt"]},
    version=__version__,  # noqa: F821
    license="MIT",
    description="TPU-native (JAX/XLA/Pallas) DALL-E: discrete VAE, text-to-image transformer, CLIP reranking",
    long_description_content_type="text/markdown",
    keywords=[
        "artificial intelligence",
        "attention mechanism",
        "transformers",
        "text-to-image",
        "tpu",
        "jax",
    ],
    install_requires=[
        "jax",
        "numpy",
        "optax",
        "regex",
        "Pillow",
    ],
    extras_require={
        "tokenizers": ["tokenizers", "transformers", "youtokentome", "ftfy"],
        "logging": ["wandb"],
    },
    classifiers=[
        "Development Status :: 4 - Beta",
        "Intended Audience :: Developers",
        "Topic :: Scientific/Engineering :: Artificial Intelligence",
        "License :: OSI Approved :: MIT License",
        "Programming Language :: Python :: 3.10",
    ],
)
