#!/usr/bin/env python
"""Shim: `python train_clip.py ...` — CLIP trainer (beyond-reference capability)."""
from dalle_pytorch_tpu.cli.train_clip import main

if __name__ == "__main__":
    main()
